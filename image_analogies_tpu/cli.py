"""Command-line interface (SURVEY.md §2 P1, §5.6).

Mirrors the reference's flag surface — paths for A/A'/B and output, kappa,
levels, patch sizes, ANN toggle, mode — plus the TPU framework's additions:
backend/strategy/db-shards, checkpointing, structured logging, profiling, and
an `eval` command computing SSIM between two images.

    python -m image_analogies_tpu.cli run --a A.png --ap Ap.png --b B.png \
        --out Bp.png --mode filter --levels 3 --kappa 5 --backend tpu
    python -m image_analogies_tpu.cli video --a A.png --ap Ap.png \
        --frames f0.png f1.png f2.png --out-dir out/
    python -m image_analogies_tpu.cli eval --a out.png --b ref.png
    python -m image_analogies_tpu.cli report run.jsonl
"""

from __future__ import annotations

import argparse
import contextlib
import json
import os
import sys
from typing import List, Optional

import numpy as np

from image_analogies_tpu.config import PRESETS, AnalogyParams
from image_analogies_tpu.models import modes
from image_analogies_tpu.models.video import video_analogy
from image_analogies_tpu.utils.imageio import load_image, save_image
from image_analogies_tpu.utils.ssim import ssim

MODES = ("filter", "texture_by_numbers", "super_resolution",
         "texture_synthesis")


def _add_engine_flags(p: argparse.ArgumentParser) -> None:
    p.add_argument("--levels", type=int, default=None)
    p.add_argument("--kappa", type=float, default=None)
    p.add_argument("--patch-size", type=int, default=None)
    p.add_argument("--coarse-patch-size", type=int, default=None)
    p.add_argument("--backend", choices=("cpu", "tpu"), default=None)
    p.add_argument("--strategy",
                   choices=("exact", "rowwise", "batched", "wavefront",
                            "auto"),
                   default=None,
                   help="TPU scan strategy.  auto=wavefront (oracle parity "
                        "at full speed; use this).  batched: ~2x faster, "
                        "approximate (non-parity) synthesis.  exact/rowwise: "
                        "sequential VALIDATION seams, ~100-1000x slower — "
                        "never for production runs")
    from image_analogies_tpu.config import (
        EXPERIMENTAL_MATCH_MODES,
        PARITY_MATCH_MODES,
        experimental_enabled,
    )

    mm_choices = PARITY_MATCH_MODES
    if experimental_enabled():
        mm_choices = mm_choices + EXPERIMENTAL_MATCH_MODES
    p.add_argument("--match-mode",
                   choices=mm_choices,
                   default=None,
                   help="wavefront anchor scheme (auto = the parity "
                        "hybrid: exact_hi2_2p's packed fp32-grade scan "
                        "on large levels, exact_hi's merged kernel below "
                        "the measured crossover).  All listed modes hold "
                        "oracle parity; non-parity A/B probes appear only "
                        "with IA_EXPERIMENTAL=1 — see config.AnalogyParams")
    p.add_argument("--db-shards", type=int, default=None)
    p.add_argument("--data-shards", type=int, default=None,
                   help="video mode: shard frames over this many mesh "
                        "devices (two_phase scheme, data x db mesh); on a "
                        "single image (wavefront): split each "
                        "anti-diagonal's queries over the mesh 'data' "
                        "axis (query-parallel, bit-equal to solo)")
    p.add_argument("--refine-passes", type=int, default=None,
                   help="batched strategy: left-propagation refinement "
                        "passes per scan row")
    p.add_argument("--no-ann", action="store_true",
                   help="disable the cKDTree index (CPU backend brute force)")
    p.add_argument("--ann-prefilter", action="store_true",
                   help="two-stage TPU matcher: a PCA-projected prefilter "
                        "ranks the whole exemplar DB cheaply and the exact "
                        "f32 scorer re-scores only the top-m slab "
                        "(tune: ann_top_m / ann_proj_dims).  Gated by a "
                        "first-use oracle-parity probe per device class; "
                        "refused or unsupported requests silently run the "
                        "exact matcher (ann.fallback_exact)")
    p.add_argument("--no-remap", action="store_true",
                   help="disable luminance remapping")
    p.add_argument("--no-gaussian", action="store_true",
                   help="unweighted (flat) neighborhood distances")
    p.add_argument("--no-level-sync", action="store_true",
                   help="pipeline pyramid levels (enqueue all device work, "
                        "one sync before the final fetch) — faster on "
                        "high-latency links; per-level stats then report "
                        "enqueue_ms.  Level retries force the sync back "
                        "on, and per-level host consumers "
                        "(--checkpoint-dir, --save-levels, --log-path) "
                        "still fetch each level as it completes (see "
                        "config.AnalogyParams.level_sync)")
    p.add_argument("--level-retries", type=int, default=None,
                   help="retry a level on transient device faults this many "
                        "times (level-granular recovery, SURVEY.md 5.3)")
    p.add_argument("--dispatch-timeout-s", type=float, default=None,
                   help="watchdog deadline around each level's device "
                        "dispatch; a wedged dispatch raises a TRANSIENT "
                        "WatchdogTimeout (recovered by --level-retries) "
                        "instead of hanging the run.  0 = inline, no "
                        "watchdog thread")
    p.add_argument("--checkpoint-dir", default=None)
    p.add_argument("--resume-from-level", type=int, default=None)
    p.add_argument("--log-path", default=None)
    p.add_argument("--metrics", action="store_true",
                   help="run-scoped observability (obs/): per-run metrics "
                        "registry + span tracing; with --log-path the "
                        "run_id-stamped records feed `report`.  Off by "
                        "default and near-zero-cost when off")
    p.add_argument("--metrics-port", type=int, default=None, metavar="PORT",
                   help="bind a loopback /metrics + /healthz exposition "
                        "server (obs/live.py) for the duration of the "
                        "command, scraping the LIVE registry mid-run "
                        "(implies --metrics; 0 = ephemeral port, printed "
                        "to stderr)")
    p.add_argument("--profile-dir", default=None)
    p.add_argument("--save-levels", dest="save_levels_dir", default=None,
                   metavar="DIR",
                   help="write each level's B' plane as DIR/level_XX.png "
                        "(coarse-to-fine visual debugging)")
    p.add_argument("--shape-buckets", action="store_true",
                   help="bucket per-level DB row counts (tune/buckets.py) "
                        "so differently-sized exemplars reuse jit "
                        "programs; IA_SHAPE_BUCKETS overrides either way")
    p.add_argument("--compile-cache-dir", default=None, metavar="DIR",
                   help="JAX persistent compilation cache dir — compiles "
                        "survive process restarts (pairs with `warmup`; "
                        "IA_COMPILE_CACHE_DIR overrides)")
    p.add_argument("--devcache-bytes", type=int, default=None,
                   help="device-upload cache byte budget "
                        "(utils/devcache.py; IA_DEVCACHE_BYTES overrides)")
    p.add_argument("--catalog-dir", default=None, metavar="DIR",
                   help="exemplar catalog root (catalog/): precomputed "
                        "per-level feature pyramids resolve tier-by-tier "
                        "(HBM -> host RAM -> disk) before any cold build; "
                        "IA_CATALOG_DIR overrides")
    p.add_argument("--catalog-host-bytes", type=int, default=None,
                   help="host-RAM catalog tier byte budget "
                        "(IA_CATALOG_HOST_BYTES overrides; default 256 MiB)")
    p.add_argument("--coordinator", default=None,
                   help="multi-host: coordinator address host:port "
                        "(jax.distributed); see parallel/distributed.py")
    p.add_argument("--num-processes", type=int, default=None)
    p.add_argument("--process-id", type=int, default=None)


def _params_from_args(args, base: AnalogyParams) -> AnalogyParams:
    kw = {}
    for name in ("levels", "kappa", "backend", "strategy", "match_mode",
                 "db_shards", "data_shards", "refine_passes",
                 "level_retries", "dispatch_timeout_s", "checkpoint_dir",
                 "resume_from_level",
                 "log_path", "profile_dir", "save_levels_dir",
                 "compile_cache_dir"):
        v = getattr(args, name)
        if v is not None:
            kw[name] = v
    if args.shape_buckets:
        kw["shape_buckets"] = True
    if args.devcache_bytes is not None:
        kw["devcache_max_bytes"] = args.devcache_bytes
    if getattr(args, "catalog_dir", None) is not None:
        kw["catalog_dir"] = args.catalog_dir
    if getattr(args, "catalog_host_bytes", None) is not None:
        kw["catalog_host_bytes"] = args.catalog_host_bytes
    if args.patch_size is not None:
        kw["patch_size"] = args.patch_size
    if args.coarse_patch_size is not None:
        kw["coarse_patch_size"] = args.coarse_patch_size
    if args.no_ann:
        kw["use_ann"] = False
    if getattr(args, "ann_prefilter", False):
        kw["ann_prefilter"] = True
    if args.metrics or getattr(args, "metrics_port", None) is not None:
        kw["metrics"] = True
    if args.no_level_sync:
        kw["level_sync"] = False
    if args.no_remap:
        kw["remap_luminance"] = False
    if args.no_gaussian:
        kw["gaussian_weights"] = False
    return base.replace(**kw)


def _emit_stats(res) -> None:
    for st in res.stats:
        print(json.dumps(st, sort_keys=True), file=sys.stderr)


@contextlib.contextmanager
def _maybe_metrics_server(args):
    """Bind the obs/live exposition server for the command's duration
    when --metrics-port was given; no-op (and no obs.live import)
    otherwise."""
    port = getattr(args, "metrics_port", None)
    if port is None:
        yield None
        return
    from image_analogies_tpu.obs import live as obs_live

    httpd = obs_live.start_http_server(port)
    bound = httpd.server_address[1]
    print(f"metrics: http://127.0.0.1:{bound}/metrics "
          f"(and /healthz)", file=sys.stderr)
    try:
        yield httpd
    finally:
        obs_live.stop_http_server(httpd)


def cmd_run(args) -> int:
    mode = args.mode
    base = {
        "filter": PRESETS["oil_filter"],
        "texture_by_numbers": PRESETS["texture_by_numbers"],
        "super_resolution": PRESETS["super_resolution"],
        "texture_synthesis": PRESETS["texture_synthesis"],
    }[mode]
    params = _params_from_args(args, base)

    ap = load_image(args.ap)
    with _maybe_metrics_server(args):
        if mode == "texture_synthesis":
            shape = tuple(int(x) for x in args.out_shape.split("x"))
            res = modes.texture_synthesis(ap, shape, params, seed=args.seed)
        elif mode == "super_resolution":
            # A is derived by degrading A'; only A' and B are needed.
            b = load_image(args.b)
            res = modes.super_resolution(ap, b, params,
                                         blur_passes=args.blur_passes)
        else:
            a = load_image(args.a)
            b = load_image(args.b)
            if mode == "filter":
                res = modes.artistic_filter(a, ap, b, params)
            else:
                res = modes.texture_by_numbers(a, ap, b, params)
    save_image(args.out, res.bp)
    _emit_stats(res)
    print(args.out)
    return 0


def cmd_video(args) -> int:
    a = load_image(args.a)
    ap = load_image(args.ap)
    frames = [load_image(f) for f in args.frames]
    base = PRESETS["video"]
    params = _params_from_args(args, base)
    if args.temporal_weight is not None:
        params = params.replace(temporal_weight=args.temporal_weight)
    with _maybe_metrics_server(args):
        res = video_analogy(a, ap, frames, params, scheme=args.scheme)
    os.makedirs(args.out_dir, exist_ok=True)
    outs = []
    for t, frame in enumerate(res.frames):
        path = os.path.join(args.out_dir, f"frame_{t:04d}.png")
        save_image(path, frame)
        outs.append(path)
    for st in res.stats:
        print(json.dumps(st, sort_keys=True), file=sys.stderr)
    print("\n".join(outs))
    return 0


def cmd_sweep(args) -> int:
    """Kappa sweep (BASELINE config 3: 'super-res, 7x7 patches, kappa
    sweeps'): run one mode across a list of kappa values, write each output,
    and report SSIM against a reference image when given."""
    ap_img = load_image(args.ap)
    b = load_image(args.b)
    a = load_image(args.a) if args.a else None
    ref = load_image(args.ref) if args.ref else None
    base = {
        "filter": PRESETS["oil_filter"],
        "super_resolution": PRESETS["super_resolution"],
    }[args.mode]
    os.makedirs(args.out_dir, exist_ok=True)
    with _maybe_metrics_server(args):
        for k in (float(x) for x in args.kappas.split(",")):
            params = _params_from_args(args, base).replace(kappa=k)
            if args.mode == "super_resolution":
                res = modes.super_resolution(ap_img, b, params,
                                             blur_passes=args.blur_passes)
            else:
                res = modes.artistic_filter(a, ap_img, b, params)
            out = os.path.join(args.out_dir, f"kappa_{k:g}.png")
            save_image(out, res.bp)
            rec = {"kappa": k, "out": out}
            if ref is not None:
                rec["ssim_vs_ref"] = round(ssim(np.clip(res.bp, 0, 1), ref),
                                           4)
            print(json.dumps(rec))
    return 0


def cmd_eval(args) -> int:
    x = load_image(args.a)
    y = load_image(args.b)
    print(json.dumps({"ssim": ssim(x, y)}))
    return 0


def cmd_report(args) -> int:
    """Analyze a run-log JSONL (obs/report.py): per-level timing
    breakdown, counter totals, retry/coherence summaries, compile/HBM
    sections, manifest.  --json prints the analyze() dict per run."""
    from image_analogies_tpu.obs import report as obs_report

    if not os.path.exists(args.log):
        print(f"report: no such log: {args.log}", file=sys.stderr)
        return 2
    if args.json:
        print(obs_report.report_json(args.log))
    else:
        print(obs_report.report(args.log))
    return 0


def cmd_tune(args) -> int:
    """Measured autotuning of kernel geometry (tune/autotune.py): sweep
    candidate tiles on the live device with min-of-k timing, verify
    bit-identical champion picks across candidates, persist winners to
    the tune store.  --dry-run prints the plan and never touches the
    device."""
    from image_analogies_tpu.tune import autotune

    cands = (tuple(int(x) for x in args.candidates.split(","))
             if args.candidates else None)
    if not args.dry_run:
        import jax
        jax.devices()  # init the backend so keys carry the real device kind
    plan = autotune.build_plan(knob=args.knob, rows=args.rows, f=args.f,
                               m=args.m, reps=args.reps, candidates=cands,
                               store=args.store)
    if args.dry_run:
        print(json.dumps(plan, indent=2, sort_keys=True))
        return 0
    import jax
    interpret = args.interpret or jax.default_backend() != "tpu"
    res = autotune.run_plan(plan, interpret=interpret,
                            persist=not args.no_persist)
    print(json.dumps(res, indent=2, sort_keys=True))
    return 0 if res["all_verified"] else 1


def cmd_warmup(args) -> int:
    """AOT-compile the jit signatures for a target resolution
    (tune/warmup.py) — with --compile-cache-dir the XLA programs persist
    across processes; with --shape-buckets any same-bucket image then
    reuses them."""
    from image_analogies_tpu.tune import warmup as tune_warmup

    base = PRESETS["oil_filter"].replace(backend="tpu")
    params = _params_from_args(args, base)
    h, w = (int(x) for x in args.size.split("x"))
    eh = ew = None
    if args.exemplar_size:
        eh, ew = (int(x) for x in args.exemplar_size.split("x"))
    res = tune_warmup.warmup(params, h, w, exemplar_height=eh,
                             exemplar_width=ew, seed=args.seed)
    print(json.dumps(res, sort_keys=True))
    return 0


def cmd_serve(args) -> int:
    """Serving scheduler (serve/): micro-batching with admission control,
    deadlines, and graceful degradation.  --selftest replays a synthetic
    mixed-shape load and prints the latency/throughput summary; --http
    binds the optional loopback stdlib front end."""
    from image_analogies_tpu.serve.server import Server
    from image_analogies_tpu.serve.types import ServeConfig

    base = PRESETS["oil_filter"]
    params = _params_from_args(args, base)
    # --deadline-ms: scalar -> the server-wide default; comma list (mixed
    # load, "none" entries = undeadlined) -> cycled per selftest request.
    deadline_ms = None
    if args.deadline_ms is not None:
        parts = [None if p.lower() in ("none", "") else float(p)
                 for p in str(args.deadline_ms).split(",")]
        deadline_ms = parts[0] if len(parts) == 1 else tuple(parts)
    warmup_sizes = ()
    if args.warmup:
        warmup_sizes = tuple(
            tuple(int(x) for x in chunk.split("x"))
            for chunk in args.warmup.split(","))
    cfg = ServeConfig(
        params=params,
        queue_depth=args.queue_depth,
        batch_window_ms=args.batch_window_ms,
        max_batch=args.max_batch,
        workers=args.workers,
        default_deadline_s=(deadline_ms / 1e3
                            if isinstance(deadline_ms, (int, float))
                            else None),
        degrade=not args.no_degrade,
        request_retries=args.request_retries,
        warmup_sizes=warmup_sizes,
        deadline_ordering=not args.no_deadline_ordering,
        breaker_threshold=args.breaker_threshold,
        cost_persist=not args.no_cost_persist,
        slo_target=args.slo_target,
        slo_fast_window_s=args.slo_fast_window_s,
        slo_slow_window_s=args.slo_slow_window_s,
        journal_dir=args.journal,
        batch_engine=not args.no_batch_engine,
        ledger=not args.no_ledger,
    )

    if args.selftest is not None:
        from image_analogies_tpu.serve import loadgen

        flash_crowd = (loadgen.parse_flash_crowd(args.flash_crowd)
                       if args.flash_crowd else None)
        with _maybe_metrics_server(args):
            summary = loadgen.selftest(cfg, args.selftest, seed=args.seed,
                                       deadline_ms=deadline_ms,
                                       zipf=args.zipf, styles=args.styles,
                                       flash_crowd=flash_crowd)
        print(loadgen.render(summary))
        print(json.dumps(summary, sort_keys=True), file=sys.stderr)
        return 0 if (summary["errors"] == 0
                     and summary["bit_identical"]) else 1

    if args.http is None:
        print("serve: pass --selftest N or --http PORT", file=sys.stderr)
        return 2

    from image_analogies_tpu.obs import archive as obs_archive
    from image_analogies_tpu.obs import ceilings as obs_ceilings
    from image_analogies_tpu.obs import timeline as obs_timeline
    from image_analogies_tpu.serve.http import serve_http

    with Server(cfg) as srv:
        # single-server deployment: arm the temporal plane and run its
        # own background sampler (the fleet path samples per worker from
        # its health daemon instead) so /timeline and `ia top` are live
        tl = obs_timeline.arm()
        # witness + watchdog planes ride the same sampler as feeders
        archive_root = args.archive or os.environ.get("IA_ARCHIVE_DIR")
        if archive_root:
            obs_archive.arm(root=archive_root)
        obs_ceilings.arm()
        tl.start_sampler(interval_s=1.0)
        httpd = serve_http(srv, args.http)
        print(f"serving on http://127.0.0.1:{args.http} "
              f"(POST /v1/analogy, GET /healthz, GET /metrics, "
              f"GET /timeline, GET /tenants, GET /archive/stats); "
              f"Ctrl-C to drain+exit")
        try:
            httpd.serve_forever()
        except KeyboardInterrupt:
            pass
        finally:
            httpd.shutdown()
            obs_ceilings.disarm()
            if archive_root:
                obs_archive.disarm()
            obs_timeline.disarm()
    return 0


def cmd_fleet(args) -> int:
    """Router + worker fleet (serve/fleet.py, serve/router.py): N
    in-process servers behind a consistent-hash router with health-gated
    spillover and dead-worker journal handoff.  --selftest routes the
    synthetic load through the ring and gates on bit-identity; --http
    binds the loopback front end on the fleet."""
    from image_analogies_tpu.serve.types import FleetConfig, ServeConfig

    base = PRESETS["oil_filter"]
    params = _params_from_args(args, base)
    scfg = ServeConfig(
        params=params,
        queue_depth=args.queue_depth,
        batch_window_ms=args.batch_window_ms,
        max_batch=args.max_batch,
        workers=args.workers,
        cost_persist=False,
        journal_dir=None,  # per-worker dirs derive from journal_root
    )
    # --policy FILE > --autoscale > static fleet.  With bare --autoscale
    # the declarative defaults apply except the ceiling, which --size
    # already names: the fleet breathes between the policy floor and the
    # size the operator asked for.
    policy = None
    if args.policy:
        from image_analogies_tpu.serve.policy import ControlPolicy
        policy = ControlPolicy.load(args.policy)
    elif args.autoscale:
        from image_analogies_tpu.serve.policy import ControlPolicy
        policy = ControlPolicy(max_workers=max(1, args.size))
    fcfg = FleetConfig(
        serve=scfg,
        size=args.size,
        journal_root=args.journal,
        wire=args.wire,
        transport=args.transport,
        policy=policy,
    )

    if args.selftest is not None:
        from image_analogies_tpu.serve import loadgen

        flash_crowd = (loadgen.parse_flash_crowd(args.flash_crowd)
                       if args.flash_crowd else None)
        summary = loadgen.fleet_selftest(fcfg, args.selftest,
                                         seed=args.seed,
                                         zipf=args.zipf,
                                         styles=args.styles,
                                         flash_crowd=flash_crowd)
        print(loadgen.render_fleet(summary))
        print(json.dumps(summary, sort_keys=True), file=sys.stderr)
        return 0 if (summary["errors"] == 0
                     and summary["bit_identical"]) else 1

    if args.http is None:
        print("fleet: pass --selftest N or --http PORT", file=sys.stderr)
        return 2

    from image_analogies_tpu.serve.fleet import Fleet
    from image_analogies_tpu.serve.http import serve_fleet_http

    with Fleet(fcfg) as fl:
        httpd = serve_fleet_http(fl, args.http)
        print(f"fleet of {fcfg.size} serving on "
              f"http://127.0.0.1:{args.http} "
              f"(POST /v1/analogy, GET /healthz, GET /timeline); "
              f"Ctrl-C to drain+exit")
        try:
            httpd.serve_forever()
        except KeyboardInterrupt:
            pass
        finally:
            httpd.shutdown()
    return 0


def cmd_chaos(args) -> int:
    """Seeded fault-injection drills (chaos/): run a workload under a
    fault plan and assert full recovery — bit-identical output, no lost
    or hung request, and injection counters reconciled against the
    recovery counters they should have caused.  --selftest runs one
    canonical drill per fault kind plus the schedule-determinism check;
    --plan FILE replays a custom ChaosPlan JSON."""
    from image_analogies_tpu.chaos import ChaosPlan
    from image_analogies_tpu.chaos import runner as chaos_runner

    if args.selftest:
        kinds = args.kinds.split(",") if args.kinds else None
        result = chaos_runner.selftest(seed=args.seed, kinds=kinds)
    elif args.plan:
        try:
            plan = ChaosPlan.load(args.plan)
        except (OSError, ValueError) as exc:
            print(f"chaos: bad plan {args.plan}: {exc}", file=sys.stderr)
            return 2
        report = chaos_runner.run_drill(plan)
        report.setdefault("kind", plan.name or "plan")
        result = {"seed": plan.seed, "ok": report["ok"],
                  "reports": [report]}
    else:
        print("chaos: pass --plan FILE or --selftest", file=sys.stderr)
        return 2
    print(chaos_runner.render(result))
    if args.json:
        print(json.dumps(result, sort_keys=True, default=str),
              file=sys.stderr)
    return 0 if result["ok"] else 1


def cmd_soak(args) -> int:
    """Trace-driven soak (soak/): replay a seeded TraceSpec against an
    autoscaling fleet with chaos armed the whole run, then gate on the
    duration-emergent invariants — zero-loss accounting, audit-subset
    bit-identity, the DDSketch p99.9 bound, zero ceiling alarms, and
    journals bounded under autocompaction.  Exits non-zero on a red
    gate; failing verdicts name an `ia why`-linkable culprit key."""
    from image_analogies_tpu.soak import driver as soak_driver
    from image_analogies_tpu.soak import invariants as soak_invariants
    from image_analogies_tpu.soak import trace as soak_trace

    if args.spec:
        try:
            spec = soak_trace.TraceSpec.load(args.spec)
        except (OSError, ValueError) as exc:
            print(f"soak: bad spec {args.spec}: {exc}", file=sys.stderr)
            return 2
    elif args.full:
        spec = soak_trace.full_spec(seed=args.seed)
    else:
        spec = soak_trace.smoke_spec(seed=args.seed)
    result = soak_driver.run(spec, workdir=args.workdir)
    sys.stdout.write(soak_invariants.render(result))
    if args.workdir:
        print(f"artifacts kept under {args.workdir} — runbook: "
              f"ia why <culprit> --root "
              f"{result['facts'].get('journal_root')}; "
              f"ia archive inspect {result['facts'].get('archive_root')}")
    if args.json:
        print(json.dumps(result, sort_keys=True, default=str),
              file=sys.stderr)
    return 0 if result["ok"] else 1


def cmd_journal(args) -> int:
    """Write-ahead journal tooling (serve/journal.py).  ``inspect`` is a
    read-only summary of a journal directory — segments, per-state
    request counts, incomplete and poisoned keys; ``compact`` rewrites
    it to its minimal equivalent (final state per key, finished input
    spills dropped, response spills kept for dedupe)."""
    from image_analogies_tpu.serve.journal import RequestJournal

    if not os.path.isdir(args.dir):
        print(f"journal: no such directory {args.dir}", file=sys.stderr)
        return 2
    jr = RequestJournal(args.dir)
    if args.action == "inspect":
        info = jr.inspect()
        if args.json:
            print(json.dumps(info, indent=2, sort_keys=True))
        else:
            print(f"journal {info['path']}: {info['requests']} requests "
                  f"in {info['segments']} segment(s), {info['lines']} lines"
                  + (f", {info['corrupt_segments']} quarantined file(s)"
                     if info["corrupt_segments"] else ""))
            for st, n in sorted(info["states"].items()):
                print(f"  {st:<12} {n}")
            if info["incomplete"]:
                print(f"  incomplete   {', '.join(info['incomplete'])}")
            if info["poisoned"]:
                print(f"  poisoned     {', '.join(info['poisoned'])}")
        return 0
    if args.action == "compact":
        try:
            out = jr.compact()
        except RuntimeError as exc:  # journal active (live appender)
            print(f"journal: {exc}", file=sys.stderr)
            return 2
        if args.json:
            print(json.dumps(out, indent=2, sort_keys=True))
        else:
            print(f"compacted {args.dir}: {out['segments']} segment(s) / "
                  f"{out['lines']} lines -> 1 segment / "
                  f"{out['after']['lines']} lines "
                  f"({out['dropped_lines']} dropped)")
        return 0
    print(f"journal: unknown action {args.action}", file=sys.stderr)
    return 2


def cmd_why(args) -> int:
    """Request forensics (``ia why <idem-key>``): merge the write-ahead
    journal(s) under --root — a single ``ia serve --journal`` dir or an
    ``ia fleet --journal`` root with per-worker subdirs — with the
    sealed decision log into one ordered causal chain for a single
    request: which worker admitted it, every control-plane verdict
    (degrade, shed, spill, requeue, poison, handoff re-chain) with its
    cause, the cost vector, and the terminal state."""
    from image_analogies_tpu.serve import journal as serve_journal

    if not os.path.isdir(args.root):
        print(f"why: no such directory {args.root}", file=sys.stderr)
        return 2
    doc = serve_journal.reconstruct(args.idem, args.root)
    if args.json:
        print(json.dumps(doc, indent=2, sort_keys=True, default=str))
    else:
        sys.stdout.write(serve_journal.render_why(doc))
    return 0 if doc.get("found") else 2


def cmd_blackbox(args) -> int:
    """Render the flight-recorder dumps (obs/recorder.py) sealed into a
    journal directory on a death path — the last N records before a
    process death, breaker trip, or watchdog timeout.  Default shows the
    newest dump; ``--all`` walks every dump chronologically.  A dump
    whose integrity seal fails is reported as damaged, never rendered."""
    from image_analogies_tpu.obs import recorder as obs_recorder

    if not os.path.isdir(args.dir):
        print(f"blackbox: no such directory {args.dir}", file=sys.stderr)
        return 2
    dumps = obs_recorder.list_dumps(args.dir)
    if not dumps:
        print(f"blackbox: no dumps in {args.dir}", file=sys.stderr)
        return 1
    if not args.all:
        dumps = dumps[-1:]
    docs = []
    for path in dumps:
        try:
            docs.append((path, obs_recorder.load_dump(path)))
        except ValueError as exc:
            print(f"blackbox: {exc}", file=sys.stderr)
            return 2
    if args.json:
        print(json.dumps([doc for _path, doc in docs], indent=2,
                         sort_keys=True))
        return 0
    for path, doc in docs:
        print(f"# {os.path.basename(path)}")
        sys.stdout.write(obs_recorder.render_dump(doc, last=args.last))
    return 0


def cmd_catalog(args) -> int:
    """Exemplar catalog tooling (catalog/).  ``build`` precomputes one
    style's per-level feature pyramids and seals them under the catalog
    root; ``inspect`` is a read-only summary of the on-disk store;
    ``warm`` pre-stages entries into this process's host-RAM tier (the
    fleet-join prefetch, runnable by hand); ``gc`` prunes tmp litter,
    quarantined entries, and over-budget bytes."""
    from image_analogies_tpu.catalog import build as catalog_build
    from image_analogies_tpu.catalog import store as catalog_store
    from image_analogies_tpu.catalog import tiers as catalog_tiers

    if args.action == "build":
        a = load_image(args.a)
        ap = load_image(args.ap)
        target = load_image(args.b) if args.b else None
        base = PRESETS["oil_filter"].replace(backend="cpu")
        kw = {}
        for name in ("levels", "kappa", "patch_size", "coarse_patch_size"):
            v = getattr(args, name)
            if v is not None:
                kw[name] = v
        if args.no_remap:
            kw["remap_luminance"] = False
        rep = catalog_build.build_style(a, ap, base.replace(**kw),
                                        root_dir=args.dir, target=target)
        print(json.dumps(rep, sort_keys=True))
        return 0

    if not os.path.isdir(args.dir):
        print(f"catalog: no such directory {args.dir}", file=sys.stderr)
        return 2

    if args.action == "inspect":
        info = catalog_store.stats(args.dir)
        if args.json:
            print(json.dumps(info, indent=2, sort_keys=True))
        else:
            print(f"catalog {args.dir}: {len(info['styles'])} style(s), "
                  f"{info['entries']} entries, "
                  f"{info['bytes']} bytes"
                  + (f", {info['corrupt']} quarantined"
                     if info["corrupt"] else ""))
            for style in catalog_store.list_styles(args.dir):
                ents = catalog_store.list_entries(args.dir, style)
                print(f"  {style}  {len(ents)} entries / "
                      f"{sum(n for _, n in ents)} bytes")
        return 0

    if args.action == "warm":
        styles = ([args.style] if args.style
                  else catalog_store.list_styles(args.dir))
        total = {"styles": 0, "entries": 0, "bytes": 0}
        for style in styles:
            rep = catalog_tiers.warm(style, root_dir=args.dir)
            if rep["entries"]:
                total["styles"] += 1
                total["entries"] += rep["entries"]
                total["bytes"] += rep["bytes"]
        print(json.dumps(total, sort_keys=True))
        return 0

    if args.action == "gc":
        keep = set(args.keep.split(",")) if args.keep else None
        rep = catalog_store.gc(args.dir, keep=keep,
                               max_bytes=args.max_bytes,
                               purge_corrupt=args.purge_corrupt)
        print(json.dumps(rep, sort_keys=True))
        return 0

    print(f"catalog: unknown action {args.action}", file=sys.stderr)
    return 2


def cmd_metrics(args) -> int:
    """Prometheus exposition of a run log's latest metrics snapshot
    (obs/live.py).  Without --port, render once to stdout.  With --port,
    bind a loopback sidecar exposition server that re-reads the log per
    scrape — live telemetry for runs that did not pass --metrics-port
    themselves (the log is the transport)."""
    from image_analogies_tpu.obs import live as obs_live

    if not os.path.exists(args.log):
        print(f"metrics: no such log: {args.log}", file=sys.stderr)
        return 2
    if args.port is None:
        snap = obs_live.snapshot_from_log(args.log)
        if snap is None:
            print(f"metrics: no run_end snapshot in {args.log}",
                  file=sys.stderr)
            return 1
        sys.stdout.write(obs_live.render_prometheus(snap))
        return 0

    log = args.log
    httpd = obs_live.start_http_server(
        args.port,
        snapshot_fn=lambda: obs_live.snapshot_from_log(log),
        health_fn=lambda: obs_live.health_from_log(log))
    bound = httpd.server_address[1]
    print(f"metrics sidecar on http://127.0.0.1:{bound}/metrics "
          f"(and /healthz), re-reading {log} per scrape; Ctrl-C to exit",
          file=sys.stderr)
    try:
        httpd._ia_thread.join()
    except KeyboardInterrupt:
        pass
    finally:
        obs_live.stop_http_server(httpd)
    return 0


def _load_bench_module():
    """Import the repo-root bench.py (it is a script, not a package
    member).  Module scope there is jax-free, so `--check` stays fast."""
    import importlib.util

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(root, "bench.py")
    if not os.path.exists(path):
        raise FileNotFoundError(path)
    spec = importlib.util.spec_from_file_location("ia_bench", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def cmd_bench(args) -> int:
    """Bench entry + regression sentry.  Plain `ia bench` runs the full
    benchmark harness (bench.py).  `--check` never measures: it parses
    the BENCH_r*.json trajectory and gates a candidate number — the
    latest archived point by default, or --value/--result when given —
    against the best same-metric point, failing (exit 1) past
    --threshold percent regression."""
    try:
        bench = _load_bench_module()
    except FileNotFoundError as exc:
        print(f"bench: bench.py not found at {exc}", file=sys.stderr)
        return 2

    if args.batch:
        # batched-engine throughput point (bench.bench_batched): K lanes
        # through ONE vmapped launch, headline = marginal s/lane under
        # the distinct `batched_qps` trajectory metric
        return int(bench.bench_batched(args.batch) or 0)

    if args.exemplar_scale:
        # exemplar-DB scaling point (bench.measure_exemplar_scaling):
        # the two-stage ANN matcher against 1x/4x/16x the exemplar rows;
        # the headline exemplar_scale_ratio is what --check gates
        print(json.dumps(bench.measure_exemplar_scaling()))
        return 0

    if not args.check and not args.dry_run:
        return int(bench.main() or 0)

    bench_dir = args.dir or os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))
    trajectory = bench.load_trajectory(bench_dir)
    fresh = None
    fresh_gap = None
    fresh_obs = None
    fresh_cold = None
    fresh_scale = None
    fresh_timeline = None
    fresh_handoff = None
    fresh_ledger = None
    fresh_archive = None
    fresh_scaleup = None
    fresh_soak_p999 = None
    fresh_soak_loss = None
    fresh_key = args.metric_key
    if args.value is not None:
        fresh = args.value
    elif args.result is not None:
        try:
            with open(args.result) as f:
                doc = json.load(f)
        except (OSError, ValueError) as exc:
            print(f"bench: bad --result {args.result}: {exc}",
                  file=sys.stderr)
            return 2
        if isinstance(doc, dict) and "value" in doc:
            fresh = float(doc["value"])
            if doc.get("host_gap_ms") is not None:
                fresh_gap = float(doc["host_gap_ms"])
            if doc.get("obs_overhead_pct") is not None:
                fresh_obs = float(doc["obs_overhead_pct"])
            if doc.get("cold_start_ms") is not None:
                fresh_cold = float(doc["cold_start_ms"])
            if doc.get("exemplar_scale_ratio") is not None:
                fresh_scale = float(doc["exemplar_scale_ratio"])
            if doc.get("timeline_overhead_pct") is not None:
                fresh_timeline = float(doc["timeline_overhead_pct"])
            if doc.get("handoff_recovery_ms") is not None:
                fresh_handoff = float(doc["handoff_recovery_ms"])
            if doc.get("ledger_overhead_pct") is not None:
                fresh_ledger = float(doc["ledger_overhead_pct"])
            if doc.get("archive_overhead_pct") is not None:
                fresh_archive = float(doc["archive_overhead_pct"])
            if doc.get("scale_up_ms") is not None:
                fresh_scaleup = float(doc["scale_up_ms"])
            if doc.get("soak_p999_ms") is not None:
                fresh_soak_p999 = float(doc["soak_p999_ms"])
            if doc.get("soak_loss") is not None:
                fresh_soak_loss = int(doc["soak_loss"])
        else:
            head = bench.extract_headline(doc if isinstance(doc, dict)
                                          else {})
            if head is None:
                print(f"bench: no headline value in {args.result}",
                      file=sys.stderr)
                return 2
            fresh = head["value"]
            fresh_gap = head.get("host_gap_ms")
            fresh_obs = head.get("obs_overhead_pct")
            fresh_cold = head.get("cold_start_ms")
            fresh_scale = head.get("exemplar_scale_ratio")
            fresh_timeline = head.get("timeline_overhead_pct")
            fresh_handoff = head.get("handoff_recovery_ms")
            fresh_ledger = head.get("ledger_overhead_pct")
            fresh_archive = head.get("archive_overhead_pct")
            fresh_scaleup = head.get("scale_up_ms")
            fresh_soak_p999 = head.get("soak_p999_ms")
            fresh_soak_loss = head.get("soak_loss")
            if fresh_key is None:
                fresh_key = head.get("metric_key")
    verdict = bench.check_regression(trajectory, fresh_value=fresh,
                                     threshold_pct=args.threshold,
                                     fresh_gap=fresh_gap,
                                     fresh_key=fresh_key,
                                     fresh_obs=fresh_obs,
                                     fresh_cold=fresh_cold,
                                     fresh_scale=fresh_scale,
                                     fresh_timeline=fresh_timeline,
                                     fresh_handoff=fresh_handoff,
                                     fresh_ledger=fresh_ledger,
                                     fresh_archive=fresh_archive,
                                     fresh_scaleup=fresh_scaleup,
                                     fresh_soak_p999=fresh_soak_p999,
                                     fresh_soak_loss=fresh_soak_loss)
    print(json.dumps(verdict, sort_keys=True))
    for problem in verdict.get("problems", []):
        print(f"bench: warning: {problem}", file=sys.stderr)
    return 0 if verdict["ok"] else 1


def cmd_top(args) -> int:
    """Live terminal cockpit over a serving front end's ``/timeline``
    endpoint: QPS, windowed p50/p95, queue depth, breaker states, HBM
    peak, and anomaly flags per worker (obs/timeline.py renders; this
    command only fetches and redraws).  ``--once`` prints a single
    frame and exits — the CI-friendly mode tier-1 drives against a
    live selftest server.  ``--tenants`` switches to the per-style
    view over ``/tenants``: top-K tenants by request count with QPS,
    p95, cost share, and degrade/retry burden (obs/ledger.py)."""
    import time as _time
    import urllib.error
    import urllib.request

    from image_analogies_tpu.obs import timeline as obs_timeline

    if getattr(args, "from_archive", None):
        # Replay archived history into the cockpit: every sealed
        # timeline document becomes one frame, no server needed.
        from image_analogies_tpu.obs import archive as obs_archive

        ar = obs_archive.TelemetryArchive(args.from_archive)
        frames = ar.history("timeline")
        if not frames:
            print(f"top: no archived timeline documents under "
                  f"{args.from_archive}", file=sys.stderr)
            return 2
        if args.once:
            print(obs_timeline.render_cockpit(frames[-1]))
            return 0
        try:
            for doc in frames:
                sys.stdout.write(
                    "\x1b[2J\x1b[H" + obs_timeline.render_cockpit(doc)
                    + "\n")
                sys.stdout.flush()
                _time.sleep(args.interval)
        except KeyboardInterrupt:
            pass
        return 0

    if args.tenants:
        from image_analogies_tpu.obs import ledger as obs_ledger

        t_url = args.url.rstrip("/") + "/tenants"

        def fetch_tenants():
            with urllib.request.urlopen(t_url, timeout=5) as resp:
                return json.loads(resp.read().decode())

        if args.once:
            try:
                doc = fetch_tenants()
            except (OSError, ValueError, urllib.error.URLError) as exc:
                print(f"top: cannot fetch {t_url}: {exc}",
                      file=sys.stderr)
                return 2
            sys.stdout.write(obs_ledger.render_tenants(doc))
            return 0
        try:
            while True:
                try:
                    frame = obs_ledger.render_tenants(fetch_tenants())
                except (OSError, ValueError,
                        urllib.error.URLError) as exc:
                    frame = f"top: cannot fetch {t_url}: {exc}\n"
                sys.stdout.write("\x1b[2J\x1b[H" + frame)
                sys.stdout.flush()
                _time.sleep(args.interval)
        except KeyboardInterrupt:
            return 0

    url = args.url.rstrip("/") + "/timeline"
    if args.window is not None:
        url += f"?window={args.window:g}"
    health_url = args.url.rstrip("/") + "/healthz"

    def fetch():
        with urllib.request.urlopen(url, timeout=5) as resp:
            return json.loads(resp.read().decode())

    def fleet_line():
        # Best-effort elastic-fleet banner from /healthz: live size vs
        # configured, the control plane's last verdict, and how to
        # attribute it.  Single-server fronts (no "control" section)
        # and fetch failures render nothing.
        try:
            with urllib.request.urlopen(health_url, timeout=5) as resp:
                doc = json.loads(resp.read().decode())
        except (OSError, ValueError, urllib.error.URLError):
            return ""
        ctl = doc.get("control") if isinstance(doc, dict) else None
        if not isinstance(ctl, dict):
            return ""
        line = (f"fleet: size={ctl.get('size', '?')}"
                f"/{doc.get('configured_size', '?')} "
                f"autoscale={'on' if ctl.get('autoscale') else 'off'}")
        last = ctl.get("last_verdict")
        if isinstance(last, dict):
            line += (f"  last={last.get('verdict', '?')}"
                     f"({last.get('cause', '?')}) "
                     f"{last.get('worker', '?')} "
                     f"— ia why ctl-{last.get('verdict', '?')}-"
                     f"{last.get('worker', '?')}")
        return line + "\n"

    if args.once:
        try:
            doc = fetch()
        except (OSError, ValueError, urllib.error.URLError) as exc:
            print(f"top: cannot fetch {url}: {exc}", file=sys.stderr)
            return 2
        print(fleet_line() + obs_timeline.render_cockpit(doc))
        return 0
    try:
        while True:
            try:
                frame = (fleet_line()
                         + obs_timeline.render_cockpit(fetch()))
            except (OSError, ValueError,
                    urllib.error.URLError) as exc:
                frame = f"top: cannot fetch {url}: {exc}"
            # ANSI clear+home, then one full frame: flicker-free enough
            # for a 1 Hz cockpit without a curses dependency
            sys.stdout.write("\x1b[2J\x1b[H" + frame + "\n")
            sys.stdout.flush()
            _time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


def cmd_archive(args) -> int:
    """Offline reader over a durable telemetry archive (obs/archive.py).
    ``inspect`` summarizes the sealed store — segments, bytes, witnessed
    record kinds, quarantined files; ``replay`` reconstructs the final
    ``/timeline`` + ``/tenants`` documents exactly as the server last
    published them (the round-trip contract); ``diff`` compares two
    archives series-by-series — the before/after-an-incident view."""
    from image_analogies_tpu.obs import archive as obs_archive

    def _open(root):
        if not os.path.isdir(root):
            print(f"archive: no such directory {root}", file=sys.stderr)
            return None
        return obs_archive.TelemetryArchive(root)

    if args.action == "diff":
        a = _open(args.a)
        b = _open(args.b)
        if a is None or b is None:
            return 2
        d = obs_archive.diff_replays(a.replay(), b.replay())
        if args.json:
            print(json.dumps(d, indent=2, sort_keys=True))
        else:
            print(obs_archive.render_diff(d))
        return 0

    ar = _open(args.root)
    if ar is None:
        return 2

    if args.action == "inspect":
        info = ar.stats()
        rep = ar.replay()
        info["kinds"] = rep["kinds"]
        info["span"] = rep["span"]
        if args.json:
            print(json.dumps(info, indent=2, sort_keys=True))
            return 0
        span = rep["span"]
        dur = (span[1] - span[0]
               if span[0] is not None and span[1] is not None else 0.0)
        print(f"archive {args.root}: {info['segments']} segment(s) + "
              f"{info['summary_segments']} summary, {info['bytes']} bytes"
              + (f", {info['quarantined']} quarantined"
                 if info["quarantined"] else ""))
        kinds = ", ".join(f"{k}={n}"
                          for k, n in sorted(rep["kinds"].items()))
        print(f"  span: {dur:.1f}s  kinds: {kinds or '(empty)'}")
        return 0

    if args.action == "replay":
        from image_analogies_tpu.obs import ledger as obs_ledger
        from image_analogies_tpu.obs import timeline as obs_timeline

        rep = ar.replay()
        if args.json:
            print(json.dumps(rep, indent=2, sort_keys=True))
            return 0
        if rep["timeline"] is None and rep["tenants"] is None:
            print("archive: no witnessed timeline/tenants documents",
                  file=sys.stderr)
            return 2
        if rep["timeline"] is not None:
            print(obs_timeline.render_cockpit(rep["timeline"]))
        if rep["tenants"] is not None:
            print(obs_ledger.render_tenants(rep["tenants"],
                                            title="tenants (archived)"))
        if rep["decisions"]:
            print(f"decisions witnessed: {len(rep['decisions'])}  latest: "
                  + json.dumps(rep["decisions"][-1], sort_keys=True))
        if rep["anomalies"]:
            print(f"anomalies witnessed: {len(rep['anomalies'])}")
        return 0

    print(f"archive: unknown action {args.action}", file=sys.stderr)
    return 2


def cmd_trace(args) -> int:
    """Convert a run-log JSONL into a Chrome/Perfetto trace.json
    (obs/export.py) for chrome://tracing / ui.perfetto.dev."""
    from image_analogies_tpu.obs import export as obs_export

    if not os.path.exists(args.log):
        print(f"trace: no such log: {args.log}", file=sys.stderr)
        return 2
    res = obs_export.export_trace(args.log, args.out)
    print(f"{args.out}: {res['events']} events from "
          f"{res['records']} records")
    return 0


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="image_analogies_tpu",
        description="TPU-native Image Analogies (Hertzmann et al. 2001)")
    sub = ap.add_subparsers(dest="cmd", required=True)

    run = sub.add_parser("run", help="single-image analogy")
    run.add_argument("--mode", choices=MODES, default="filter")
    run.add_argument("--a", help="unfiltered source image A")
    run.add_argument("--ap", required=True, help="filtered source image A'")
    run.add_argument("--b", help="target image B")
    run.add_argument("--out", required=True)
    run.add_argument("--out-shape", default="256x256",
                     help="HxW for texture_synthesis")
    run.add_argument("--blur-passes", type=int, default=2,
                     help="degradation strength for super_resolution")
    run.add_argument("--seed", type=int, default=None,
                     help="texture_synthesis: noise seed for varied outputs "
                          "(omit for the deterministic degenerate analogy)")
    _add_engine_flags(run)
    run.set_defaults(fn=cmd_run)

    vid = sub.add_parser("video", help="batched video analogy")
    vid.add_argument("--a", required=True)
    vid.add_argument("--ap", required=True)
    vid.add_argument("--frames", nargs="+", required=True)
    vid.add_argument("--out-dir", required=True)
    vid.add_argument("--scheme", choices=("sequential", "two_phase"),
                     default="two_phase")
    vid.add_argument("--temporal-weight", type=float, default=None)
    _add_engine_flags(vid)
    vid.set_defaults(fn=cmd_video)

    sw = sub.add_parser("sweep", help="kappa sweep over one mode")
    sw.add_argument("--mode", choices=("filter", "super_resolution"),
                    default="super_resolution")
    sw.add_argument("--a", help="unfiltered source (filter mode)")
    sw.add_argument("--ap", required=True)
    sw.add_argument("--b", required=True)
    sw.add_argument("--kappas", default="0,0.5,1,2,5,10",
                    help="comma-separated kappa values")
    sw.add_argument("--out-dir", required=True)
    sw.add_argument("--ref", default=None,
                    help="reference image for per-kappa SSIM")
    sw.add_argument("--blur-passes", type=int, default=2)
    _add_engine_flags(sw)
    sw.set_defaults(fn=cmd_sweep)

    ev = sub.add_parser("eval", help="SSIM between two images")
    ev.add_argument("--a", required=True)
    ev.add_argument("--b", required=True)
    ev.set_defaults(fn=cmd_eval)

    rp = sub.add_parser("report",
                        help="analyze a run-log JSONL (--log-path output): "
                             "per-level timing, counters, compile/HBM, "
                             "manifest")
    rp.add_argument("log", help="path to the run-log JSONL")
    rp.add_argument("--json", action="store_true",
                    help="machine-readable output: the analyze() dict per "
                         "run (levels, counters, compile, hbm)")
    rp.set_defaults(fn=cmd_report)

    tr = sub.add_parser("trace",
                        help="convert a run-log JSONL into a Chrome/"
                             "Perfetto trace.json (host/device/compile "
                             "tracks)")
    tr.add_argument("log", help="path to the run-log JSONL")
    tr.add_argument("-o", "--out", default="trace.json",
                    help="output trace path (default: trace.json)")
    tr.set_defaults(fn=cmd_trace)

    tp = sub.add_parser("top",
                        help="live terminal cockpit over a serving front "
                             "end's /timeline endpoint (QPS, windowed "
                             "p50/p95, queue depth, breakers, HBM, "
                             "anomalies per worker)")
    tp.add_argument("--url", default="http://127.0.0.1:8080",
                    help="serving front end base URL "
                         "(default: http://127.0.0.1:8080)")
    tp.add_argument("--interval", type=float, default=1.0,
                    help="refresh period in seconds (default: 1.0)")
    tp.add_argument("--window", type=float, default=None,
                    help="downsampling tier to read (e.g. 10 or 60; "
                         "default: the finest)")
    tp.add_argument("--once", action="store_true",
                    help="print one frame and exit (CI mode)")
    tp.add_argument("--tenants", action="store_true",
                    help="per-style view over /tenants instead of the "
                         "worker cockpit: top-K tenants by request "
                         "count with QPS, p95, cost share, and degrade/"
                         "retry burden (space-saving heavy hitters)")
    tp.add_argument("--from-archive", default=None, metavar="ROOT",
                    help="replay a durable telemetry archive instead of "
                         "scraping a live server: each sealed timeline "
                         "document renders as one cockpit frame at "
                         "--interval pace (--once shows only the final "
                         "frame)")
    tp.set_defaults(fn=cmd_top)

    mx = sub.add_parser("metrics",
                        help="Prometheus text exposition of a run log's "
                             "metrics: once to stdout, or as a loopback "
                             "sidecar server with --port")
    mx.add_argument("log", help="run-log JSONL (--log-path output)")
    mx.add_argument("--port", type=int, default=None, metavar="PORT",
                    help="bind a sidecar /metrics + /healthz server that "
                         "re-reads the log per scrape (0 = ephemeral)")
    mx.set_defaults(fn=cmd_metrics)

    bn = sub.add_parser("bench",
                        help="run the benchmark harness, or with --check "
                             "gate a wall-clock number against the "
                             "BENCH_r*.json trajectory (regression sentry)")
    bn.add_argument("--batch", type=int, default=None, metavar="K",
                    help="measure the batched B-axis engine instead of "
                         "the full harness: K lanes through one vmapped "
                         "launch vs K sequential singletons, gated on "
                         "bit-identity; records the 'batched_qps' "
                         "trajectory metric (marginal s/lane, lower is "
                         "better)")
    bn.add_argument("--exemplar-scale", action="store_true",
                    help="measure the two-stage ANN matcher against "
                         "1x/4x/16x the exemplar DB rows instead of the "
                         "full harness; prints the per-scale s and "
                         "s-per-Mrow points plus the exemplar_scale_ratio "
                         "headline that --check gates (relative floor + "
                         "absolute sub-linearity)")
    bn.add_argument("--check", action="store_true",
                    help="no measurement: parse the trajectory and fail "
                         "(exit 1) when the candidate regresses past "
                         "--threshold over the best same-metric point")
    bn.add_argument("--dry-run", action="store_true",
                    help="alias for the no-measurement check path (tier-1 "
                         "smoke: proves the archive still parses)")
    bn.add_argument("--value", type=float, default=None,
                    help="fresh wall-clock seconds to gate (e.g. a number "
                         "just measured out-of-band)")
    bn.add_argument("--result", default=None, metavar="FILE",
                    help="JSON file carrying the fresh number: a bench "
                         "headline line or a BENCH_r0N.json driver doc")
    bn.add_argument("--metric-key", default=None,
                    help="metric the fresh --value belongs to (e.g. "
                         "north_star_1024); defaults to --result's "
                         "parsed key, else the latest archived point's. "
                         "A key with no archived floor passes as "
                         "'no floor, recorded only' instead of gating "
                         "against an unrelated metric")
    bn.add_argument("--threshold", type=float, default=20.0,
                    help="max tolerated regression percent (default 20)")
    bn.add_argument("--dir", default=None,
                    help="directory holding BENCH_r*.json (default: repo "
                         "root)")
    bn.set_defaults(fn=cmd_bench)

    # tune takes NO engine flags (and so skips the distributed-init gate):
    # --dry-run must never touch the device.
    tn = sub.add_parser("tune",
                        help="measured kernel-geometry autotuning: sweep "
                             "tile candidates on the live device, verify "
                             "bit-identical picks, persist winners to the "
                             "tune store (.ia_tune.json)")
    tn.add_argument("--dry-run", action="store_true",
                    help="print the sweep plan JSON; no device work")
    tn.add_argument("--knob",
                    choices=("packed_tile", "argmin_tile", "ann", "all"),
                    default="all",
                    help="ann sweeps ann_top_m with full two-stage "
                         "syntheses, each tie-audited against an exact "
                         "run before persistence; NOT part of 'all' "
                         "(minutes, and it exercises the parity gate)")
    tn.add_argument("--store", default=None,
                    help="tune store path (default: repo .ia_tune.json, "
                         "IA_TUNE_STORE overrides)")
    tn.add_argument("--rows", type=int, default=262144,
                    help="synthetic DB row count (padded per candidate)")
    tn.add_argument("--f", type=int, default=253,
                    help="raw feature width for the argmin sweep")
    tn.add_argument("--m", type=int, default=1024,
                    help="query batch size")
    tn.add_argument("--reps", type=int, default=5,
                    help="timed reps per candidate (min-of-k)")
    tn.add_argument("--candidates", default=None,
                    help="comma-separated tile candidates (overrides the "
                         "per-knob default grid)")
    tn.add_argument("--interpret", action="store_true",
                    help="force Pallas interpret mode (auto on non-TPU)")
    tn.add_argument("--no-persist", action="store_true",
                    help="measure + verify but do not write the store")
    tn.set_defaults(fn=cmd_tune)

    sv = sub.add_parser("serve",
                        help="serving scheduler: micro-batched dispatch "
                             "with admission control, per-request "
                             "deadlines, and graceful degradation "
                             "(--selftest N for the synthetic load, "
                             "--http PORT for the loopback front end)")
    sv.add_argument("--selftest", type=int, default=None, metavar="N",
                    help="replay N synthetic mixed-shape requests against "
                         "a sequential baseline and print the latency/"
                         "throughput/degradation summary")
    sv.add_argument("--http", type=int, default=None, metavar="PORT",
                    help="bind the loopback-only stdlib HTTP front end")
    sv.add_argument("--queue-depth", type=int, default=32,
                    help="admission bound; requests beyond it are "
                         "Rejected(queue_full) immediately")
    sv.add_argument("--batch-window-ms", type=float, default=4.0,
                    help="coalescing window once a batch leader is held")
    sv.add_argument("--max-batch", type=int, default=8)
    sv.add_argument("--workers", type=int, default=2)
    sv.add_argument("--deadline-ms", default=None,
                    help="default per-request deadline; expired before "
                         "dispatch -> cancelled, unmeetable -> degraded "
                         "(fewer levels / coarser patch), flagged in the "
                         "response.  With --selftest a comma list (e.g. "
                         "300,none) cycles per request — a mixed-deadline "
                         "load exercising the queue's EDF ordering")
    sv.add_argument("--no-degrade", action="store_true",
                    help="never degrade: unmeetable deadlines run full "
                         "fidelity anyway (only already-expired requests "
                         "time out)")
    sv.add_argument("--request-retries", type=int, default=1,
                    help="transparent retries around each dispatch on "
                         "transient device faults")
    sv.add_argument("--warmup", default=None, metavar="SIZES",
                    help="comma-separated HxW list (e.g. 64x64,128x128) to "
                         "AOT-precompile before accepting traffic")
    sv.add_argument("--no-deadline-ordering", action="store_true",
                    help="pop batch leaders FIFO instead of earliest-"
                         "deadline-first (EDF with an aging bound is the "
                         "default; it cuts timeout rate under mixed-"
                         "deadline load)")
    sv.add_argument("--breaker-threshold", type=int, default=5,
                    help="consecutive dispatch failures that trip the "
                         "worker circuit breaker (fail-fast "
                         "Rejected(circuit_open) until a half-open probe "
                         "succeeds); 0 disables")
    sv.add_argument("--no-cost-persist", action="store_true",
                    help="do not persist the measured degrade cost rate "
                         "to the tune store at shutdown (persistence "
                         "seeds the next server's admission estimates)")
    sv.add_argument("--slo-target", type=float, default=0.99,
                    help="SLO: target fraction of deadlined requests that "
                         "meet their deadline (obs/slo.py burn-rate "
                         "gauges, /healthz slo section)")
    sv.add_argument("--slo-fast-window-s", type=float, default=60.0,
                    help="fast (paging) burn-rate window seconds")
    sv.add_argument("--slo-slow-window-s", type=float, default=600.0,
                    help="slow (ticket) burn-rate window seconds")
    sv.add_argument("--journal", default=None, metavar="DIR",
                    help="write-ahead request journal directory: every "
                         "request is recorded at admit and on each state "
                         "transition; on startup the server replays it — "
                         "finished requests dedupe exactly-once, "
                         "interrupted ones re-enqueue, poison ones shed "
                         "(omit to disable; disabled costs nothing)")
    sv.add_argument("--no-batch-engine", action="store_true",
                    help="dispatch every batch member as its own engine "
                         "call instead of fusing compatible same-key "
                         "batches into one batched B-axis launch "
                         "(batch/engine.py); outputs are bit-identical "
                         "either way")
    sv.add_argument("--no-ledger", action="store_true",
                    help="disarm the tenant metering plane (per-request "
                         "cost vectors, /tenants heavy hitters); the "
                         "disarmed path costs one bool check per request")
    sv.add_argument("--zipf", type=float, default=None, metavar="S",
                    help="selftest load: draw requests over --styles "
                         "synthetic styles with Zipf(S)-skewed frequency "
                         "(rank r picked with p ~ r**-S; S~1 = one viral "
                         "style dominating) instead of cycling shapes")
    sv.add_argument("--styles", type=int, default=0,
                    help="style count for --zipf (default 8)")
    sv.add_argument("--flash-crowd", default=None, metavar="T0,DUR,MULT",
                    help="selftest arrival shape: Poisson arrivals whose "
                         "rate multiplies by MULT inside [T0, T0+DUR) "
                         "seconds — a flash-crowd surge, deterministic "
                         "from --seed (the same generator the chaos "
                         "flash_crowd drill replays)")
    sv.add_argument("--archive", default=None, metavar="DIR",
                    help="durable telemetry archive root: closed timeline "
                         "windows, tenant cost vectors, decision records "
                         "and anomaly events stream to sealed append-only "
                         "segments under DIR (also via IA_ARCHIVE_DIR; "
                         "inspect offline with `ia archive` / "
                         "`ia top --from-archive`)")
    sv.add_argument("--seed", type=int, default=0)
    _add_engine_flags(sv)
    sv.set_defaults(fn=cmd_serve)

    fp = sub.add_parser("fleet",
                        help="router + worker fleet: consistent-hash "
                             "affinity on the batch key, health-gated "
                             "spillover, dead-worker journal handoff "
                             "(--selftest N for the routed synthetic "
                             "load, --http PORT for the loopback front "
                             "end)")
    fp.add_argument("--selftest", type=int, default=None, metavar="N",
                    help="route N synthetic mixed-shape requests through "
                         "the ring against a sequential baseline; gates "
                         "on zero errors and bit-identity")
    fp.add_argument("--http", type=int, default=None, metavar="PORT",
                    help="bind the loopback-only HTTP front end on the "
                         "fleet (fleet-view /healthz, routed "
                         "/v1/analogy)")
    fp.add_argument("--size", type=int, default=2,
                    help="number of in-process Server workers")
    fp.add_argument("--wire", choices=("auto", "binary", "json"),
                    default="auto",
                    help="router<->worker hop encoding: auto/binary "
                         "negotiate the IAF2 raw-f32 frame, json forces "
                         "the fallback list transport")
    fp.add_argument("--transport", choices=("inproc", "subprocess"),
                    default="inproc",
                    help="worker isolation: inproc keeps each worker an "
                         "in-process Server (zero-copy hops); subprocess "
                         "spawns each as a real OS process on a loopback "
                         "port — SIGKILL-able, journal lock holds a real "
                         "foreign pid, hops speak IAF2 over HTTP")
    fp.add_argument("--journal", default=None, metavar="DIR",
                    help="journal ROOT: each worker journals under "
                         "DIR/<wid>; a dead worker's directory is handed "
                         "to its replacement for exactly-once replay")
    fp.add_argument("--queue-depth", type=int, default=32)
    fp.add_argument("--batch-window-ms", type=float, default=4.0)
    fp.add_argument("--max-batch", type=int, default=8)
    fp.add_argument("--workers", type=int, default=1,
                    help="worker THREADS per server (the fleet dimension "
                         "is --size)")
    fp.add_argument("--zipf", type=float, default=None, metavar="S",
                    help="selftest load: Zipf(S)-skewed per-style "
                         "frequency over --styles synthetic styles "
                         "(see ia serve --zipf)")
    fp.add_argument("--styles", type=int, default=0,
                    help="style count for --zipf (default 8)")
    fp.add_argument("--flash-crowd", default=None, metavar="T0,DUR,MULT",
                    help="selftest arrival shape: Poisson arrivals whose "
                         "rate multiplies by MULT inside [T0, T0+DUR) "
                         "seconds (see ia serve --flash-crowd)")
    fp.add_argument("--autoscale", action="store_true",
                    help="arm the elastic control plane with the default "
                         "declarative policy (--size becomes the "
                         "ceiling): the fleet starts at the policy floor "
                         "and the reconcile loop grows/shrinks it on "
                         "observed queue depth, SLO burn, and breaker "
                         "state — every verdict lands in the decision "
                         "plane (`ia why ctl-<verdict>-<wid>`)")
    fp.add_argument("--policy", default=None, metavar="FILE",
                    help="ControlPolicy JSON file (implies autoscaling): "
                         "min/max workers, pressure/calm thresholds, "
                         "hysteresis window counts, per-direction "
                         "cooldowns; unknown keys are rejected")
    fp.add_argument("--seed", type=int, default=0)
    _add_engine_flags(fp)
    fp.set_defaults(fn=cmd_fleet)

    ch = sub.add_parser("chaos",
                        help="seeded fault-injection drills: run a "
                             "workload under a fault plan and assert "
                             "bit-identical recovery, no lost requests, "
                             "and injection/recovery counter "
                             "reconciliation")
    ch.add_argument("--plan", default=None, metavar="FILE",
                    help="ChaosPlan JSON (seed + per-site fault rules) "
                         "to replay against the matching drill workload")
    ch.add_argument("--selftest", action="store_true",
                    help="one canonical drill per kind "
                         "(transient, oom, latency, corrupt, crash, "
                         "process_death, fleet_death, batch_partial, "
                         "devcache_tier, ann_corrupt, flash_crowd) plus "
                         "the same-seed schedule-determinism check")
    ch.add_argument("--kinds", default=None,
                    help="comma-separated fault-kind subset for "
                         "--selftest (default: all)")
    ch.add_argument("--seed", type=int, default=0,
                    help="plan seed — same seed, same fault schedule")
    ch.add_argument("--json", action="store_true",
                    help="also print the full machine-readable report "
                         "to stderr")
    ch.set_defaults(fn=cmd_chaos)

    # soak takes NO engine flags (the driver builds its own CPU fleet
    # config), so it skips the distributed-init gate.
    sk = sub.add_parser("soak",
                        help="seeded trace-driven soak: replay a "
                             "TraceSpec against an autoscaling fleet "
                             "with chaos armed throughout and gate on "
                             "duration-emergent invariants (zero loss, "
                             "audit bit-identity, p99.9 bound, zero "
                             "ceiling alarms, bounded journals)")
    sk.add_argument("--spec", default=None, metavar="FILE",
                    help="TraceSpec JSON (seed, Zipf styles, diurnal + "
                         "flash-crowd shape, session/priority mixes, "
                         "chaos plan); default is the built-in smoke")
    sk.add_argument("--full", action="store_true",
                    help="run the bench-profile soak (hundreds of "
                         "requests) instead of the smoke")
    sk.add_argument("--seed", type=int, default=7,
                    help="seed for the built-in specs — same seed, "
                         "byte-identical request stream")
    sk.add_argument("--workdir", default=None, metavar="DIR",
                    help="persist journals/archive/catalog under DIR "
                         "(default: swept tempdir) so a red gate's "
                         "culprits stay reconstructable via ia why")
    sk.add_argument("--json", action="store_true",
                    help="also print the full machine-readable result "
                         "to stderr")
    sk.set_defaults(fn=cmd_soak)

    # catalog takes NO engine flags (so it skips the distributed-init
    # gate): build runs the CPU feature path, the rest is pure file io.
    ct = sub.add_parser("catalog",
                        help="exemplar catalog tooling: precompute a "
                             "style's sealed per-level feature pyramids "
                             "(build), summarize the store (inspect), "
                             "pre-stage entries into host RAM (warm), or "
                             "prune it (gc)")
    ct_sub = ct.add_subparsers(dest="action", required=True)
    cb = ct_sub.add_parser("build",
                           help="precompute + seal one style's per-level "
                                "features under the catalog root")
    cb.add_argument("--a", required=True, help="unfiltered source A")
    cb.add_argument("--ap", required=True, help="filtered source A'")
    cb.add_argument("--b", default=None,
                    help="remap anchor target: with luminance remap on, "
                         "A's planes depend on the target's luminance "
                         "stats — pass the (first) target so the sealed "
                         "entries match its requests (omit to anchor on "
                         "A itself)")
    cb.add_argument("--dir", required=True, help="catalog root directory")
    cb.add_argument("--levels", type=int, default=None)
    cb.add_argument("--kappa", type=float, default=None)
    cb.add_argument("--patch-size", type=int, default=None)
    cb.add_argument("--coarse-patch-size", type=int, default=None)
    cb.add_argument("--no-remap", action="store_true",
                    help="disable luminance remapping")
    cb.set_defaults(fn=cmd_catalog)
    ci = ct_sub.add_parser("inspect",
                           help="read-only store summary: styles, "
                                "entries, bytes, quarantined files")
    ci.add_argument("dir", help="catalog root directory")
    ci.add_argument("--json", action="store_true",
                    help="machine-readable output")
    ci.set_defaults(fn=cmd_catalog)
    cw = ct_sub.add_parser("warm",
                           help="pre-stage sealed entries into this "
                                "process's host-RAM tier (the fleet-join "
                                "prefetch, runnable by hand)")
    cw.add_argument("dir", help="catalog root directory")
    cw.add_argument("--style", default=None,
                    help="warm one style (default: every style on disk)")
    cw.set_defaults(fn=cmd_catalog)
    cg = ct_sub.add_parser("gc",
                           help="prune the disk tier: tmp litter always, "
                                "quarantined files with --purge-corrupt, "
                                "oldest entries past --max-bytes")
    cg.add_argument("dir", help="catalog root directory")
    cg.add_argument("--max-bytes", type=int, default=None,
                    help="prune oldest-first until the store fits")
    cg.add_argument("--keep", default=None,
                    help="comma-separated styles exempt from pruning")
    cg.add_argument("--purge-corrupt", action="store_true",
                    help="also remove quarantined .corrupt files "
                         "(they are evidence; default keeps them)")
    cg.set_defaults(fn=cmd_catalog)

    # archive is pure file io — no engine flags, no distributed gate.
    av = sub.add_parser("archive",
                        help="durable telemetry archive tooling: "
                             "summarize the sealed store (inspect), "
                             "reconstruct the final cockpit + tenants "
                             "documents (replay), or compare two "
                             "archives series-by-series (diff)")
    av_sub = av.add_subparsers(dest="action", required=True)
    ai = av_sub.add_parser("inspect",
                           help="read-only store summary: segments, "
                                "bytes, witnessed record kinds, "
                                "quarantined files")
    ai.add_argument("root", help="archive root directory")
    ai.add_argument("--json", action="store_true",
                    help="machine-readable output")
    ai.set_defaults(fn=cmd_archive)
    av_rp = av_sub.add_parser("replay",
                              help="reconstruct the final /timeline + "
                                   "/tenants documents from the sealed "
                                   "segments and render them as the "
                                   "cockpit would have")
    av_rp.add_argument("root", help="archive root directory")
    av_rp.add_argument("--json", action="store_true",
                       help="full replay document (timeline, tenants, "
                            "kinds, decisions, anomalies, span) as JSON")
    av_rp.set_defaults(fn=cmd_archive)
    ad = av_sub.add_parser("diff",
                           help="compare two archives' replayed state: "
                                "per-series deltas (p50/p95/p99/p999, "
                                "counts), tenants present in only one, "
                                "witnessed-kind counts")
    ad.add_argument("a", help="baseline archive root")
    ad.add_argument("b", help="comparison archive root")
    ad.add_argument("--json", action="store_true",
                    help="machine-readable output")
    ad.set_defaults(fn=cmd_archive)

    jr = sub.add_parser("journal",
                        help="write-ahead request journal tooling: "
                             "inspect a journal directory or compact it "
                             "to its minimal equivalent")
    jr.add_argument("action", choices=("inspect", "compact"),
                    help="inspect: read-only per-state summary; compact: "
                         "rewrite to one segment of final states "
                         "(finished input spills dropped, response "
                         "spills kept for dedupe); compact refuses "
                         "while a live server holds the journal")
    jr.add_argument("dir", help="journal directory (ia serve --journal)")
    jr.add_argument("--json", action="store_true",
                    help="machine-readable output")
    jr.set_defaults(fn=cmd_journal)

    wy = sub.add_parser("why",
                        help="request forensics: replay the journal(s) + "
                             "decision log into one ordered causal chain "
                             "for a single idempotency key (admit -> "
                             "verdicts with causes -> cost vector -> "
                             "terminal state)")
    wy.add_argument("idem", help="idempotency key (the journal key; "
                                 "derived content keys appear in "
                                 "`ia journal inspect`)")
    wy.add_argument("--root", required=True, metavar="DIR",
                    help="journal directory (ia serve --journal) or "
                         "fleet journal ROOT (ia fleet --journal) — "
                         "worker subdirs and decisions.jsonl are "
                         "discovered automatically")
    wy.add_argument("--json", action="store_true",
                    help="machine-readable reconstruction (events with "
                         "ts/worker/op, decisions, cost vectors, chain)")
    wy.set_defaults(fn=cmd_why)

    bb = sub.add_parser("blackbox",
                        help="render sealed flight-recorder dumps from a "
                             "journal directory (the last records before "
                             "a process death / breaker trip / watchdog "
                             "timeout)")
    bb.add_argument("dir", help="journal directory holding "
                                "blackbox-*.json dumps")
    bb.add_argument("--all", action="store_true",
                    help="render every dump (default: newest only)")
    bb.add_argument("--last", type=int, default=0,
                    help="trim each dump to its N newest records "
                         "(0 = all)")
    bb.add_argument("--json", action="store_true",
                    help="machine-readable output (seal-verified)")
    bb.set_defaults(fn=cmd_blackbox)

    wu = sub.add_parser("warmup",
                        help="AOT-compile jit signatures for a target "
                             "resolution (pairs with --compile-cache-dir "
                             "and --shape-buckets)")
    wu.add_argument("--size", default="256x256", help="target B HxW")
    wu.add_argument("--exemplar-size", default=None,
                    help="A/A' HxW (default: same as --size)")
    wu.add_argument("--seed", type=int, default=0)
    _add_engine_flags(wu)
    wu.set_defaults(fn=cmd_warmup)
    return ap


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if hasattr(args, "coordinator"):  # engine commands (not eval)
        from image_analogies_tpu.parallel.distributed import \
            initialize_distributed

        # no-ops for single-process runs; also honors the
        # JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID env
        # configuration with no flags at all
        initialize_distributed(args.coordinator, args.num_processes,
                               args.process_id)
    if args.cmd in ("run", "sweep"):
        required = {"filter": ("a", "b"), "texture_by_numbers": ("a", "b"),
                    "super_resolution": ("b",), "texture_synthesis": ()}
        missing = [k for k in required[args.mode]
                   if getattr(args, k, None) is None]
        if missing:
            build_parser().error(
                f"--{' --'.join(missing)} required for mode {args.mode}")
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())
