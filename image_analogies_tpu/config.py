"""Configuration for the Image Analogies engine.

The flag surface mirrors the reference CLI (SURVEY.md §2 P1, §5.6): paths for
A/A'/B, kappa, pyramid levels, patch sizes, ANN toggle, mode — plus the
TPU-framework additions: ``backend`` (the pluggable Matcher seam,
BASELINE.json:5), match ``strategy``, mesh shape, checkpointing.
"""

from __future__ import annotations

import dataclasses
import os
from dataclasses import dataclass
from typing import Optional, Tuple

# The production match-mode surface: every mode here is PARITY-grade (its
# picks hold the oracle tie-audit at explained ~1.0 — config docstring).
PARITY_MATCH_MODES = ("auto", "exact_hi", "exact_hi2", "exact_hi2_2p")
# Measured-A/B probe modes (NOT parity: bf16 scan resolution walks the
# synthesis away from the oracle — experiments/rescue_probe.py).  They stay
# implemented for experiments but are gated out of the user-facing surface:
# selecting one requires IA_EXPERIMENTAL=1 in the environment (round-3
# VERDICT item 7).
EXPERIMENTAL_MATCH_MODES = ("scan_rescue", "scan_rescue_1p",
                            "two_pass", "two_pass_1p")


def env_truthy(name: str, default: bool = False) -> bool:
    """Fail-closed boolean env gate: only explicit truthy spellings count,
    so typos and falsey values ("0", "disabled", ...) never open a gate.
    Unset returns ``default``.  The one spelling of this check — config
    gates (IA_EXPERIMENTAL) and serve/ env toggles share it instead of
    re-deriving their own truthiness rules."""
    raw = os.environ.get(name)
    if raw is None or not raw.strip():
        return default
    return raw.strip().lower() in ("1", "true", "yes", "on")


def experimental_enabled() -> bool:
    """True when IA_EXPERIMENTAL opts into the non-parity probe modes
    (fails closed — see :func:`env_truthy`)."""
    return env_truthy("IA_EXPERIMENTAL")


@dataclass(frozen=True)
class AnalogyParams:
    """All knobs of the synthesis engine.

    Semantics follow Hertzmann et al. 2001 (see SURVEY.md §1-§3):

    - ``levels``: Gaussian pyramid depth L.  ``levels=1`` is the supported
      single-scale degenerate case (texture-by-numbers config, BASELINE.json:7).
    - ``patch_size``: fine-level window P (odd).  5 classic, 7 for super-res.
    - ``coarse_patch_size``: window at the next-coarser level (odd), 3 classic.
    - ``kappa``: coherence weight.  Coherence candidate wins iff
      ``d_coh <= d_app * (1 + 2**(-level) * kappa)**2`` where ``level`` counts
      from the finest (0) — squared because distances are squared L2
      (Hertzmann §3.2 eq. 2; level factor 2^(l-L) with their numbering).
    - ``gaussian_weights``: Gaussian-weight neighborhood differences
      (Hertzmann §3.1); both backends share the exact weight vector.
    - ``remap_luminance``: linearly remap A/A' luminance to B's mean/std
      (Hertzmann §3.4).  Off for texture-by-numbers.
    - ``src_weight``: multiplier on the unfiltered-plane (A/B) feature blocks.
      1.0 for analogies; 0.0 turns the engine into plain texture synthesis
      (the B plane is ignored; only causal B' windows drive matching).
    - ``color_mode``: how B' gets color.  ``"yiq_transfer"`` synthesizes Y and
      carries B's IQ chroma (classic filter mode); ``"source_rgb"`` copies the
      full RGB of A'[s(q)] via the source map (texture-by-numbers / synthesis).
    """

    levels: int = 3
    patch_size: int = 5
    coarse_patch_size: int = 3
    kappa: float = 5.0
    gaussian_weights: bool = True
    remap_luminance: bool = True
    src_weight: float = 1.0
    color_mode: str = "yiq_transfer"  # "yiq_transfer" | "source_rgb"

    # Backend seam (BASELINE.json:5): only build_features()/best_match()
    # cross it.  "cpu" = NumPy/cKDTree oracle, "tpu" = JAX/Pallas.
    backend: str = "cpu"  # "cpu" | "tpu"

    # TPU match strategy:
    #   "exact"   - per-pixel on-device scan, bit-matches the oracle's
    #               candidate selection (modulo fp associativity).  Slowest:
    #               the loop-carried scan costs ~1ms/pixel in XLA.
    #   "rowwise" - batched approximate search per scan row (rows-above-only
    #               causal mask) + sequential exact coherence/kappa pass.
    #   "batched" - the fast path: the causal window is restricted to
    #               strictly-above rows for approximate AND coherence
    #               candidates, so each scan row resolves fully in parallel
    #               (one fused Pallas argmin + one batched coherence gather
    #               per row).  SURVEY.md §7 hard part 1's sanctioned lever.
    #   "wavefront" - the PARITY fast path: the raster scan re-scheduled onto
    #               anti-diagonals skewed by patch_radius+1, so every causal
    #               dependency lands on an earlier diagonal and each
    #               diagonal's pixels resolve in one batch with the oracle's
    #               exact per-pixel rule (backends/tpu.py
    #               wavefront_scan_core).  Output equals the CPU/cKDTree
    #               oracle's up to fp tie-breaks, at batched-like speed.
    #   "auto"    - wavefront.
    strategy: str = "auto"

    # Batched strategy: vectorized left-propagation refinement passes per
    # scan row (each pass lets coherent source-map runs extend patch_radius
    # pixels further left-to-right).  More passes -> closer to sequential
    # coherence, slightly slower rows.
    refine_passes: int = 3

    # How the wavefront strategy's full-DB argmin gets its pick
    # (single-chip Pallas path; the CPU oracle and the XLA fallback are
    # always exact fp32, and the mesh-sharded step scans at HIGHEST):
    #   "exact_hi2_2p" - the fast PARITY mode (auto's large-level pick):
    #                live-dim hi/mid bf16 lane-packed scan computing the
    #                four largest bf16_6x products (q1d1 + q1d2 + q2d1 +
    #                q1d3) in TWO stacked K=128 MXU passes over two bf16
    #                HBM streams.  The dropped ~2^-16-coefficient terms
    #                stay inside the tie-audit's fp-resolution band
    #                (explained=1.0, max band 6.3e-7 at 256^2; 1024^2
    #                evidence in BENCH_r03).
    #   "exact_hi2" - the conservative packed mode: full bf16_6x product
    #                set (every term with coefficient > 2^-24) in THREE
    #                stacked passes — exactly jax HIGHEST's resolution,
    #                ~1.2x slower than exact_hi2_2p (backends/tpu.py
    #                make_anchor_fn documents both packings).
    #                Round 4 upgraded its kernel in place: champion
    #                resolved in kernel scratch and norms folded into W1
    #                lanes (~2^-24 perturbation, audit-explained as fp
    #                ties).  A single-stream variant additionally
    #                dropping the ~2^-16 q1d3 term was measured and
    #                REJECTED (256^2 audit: explained 0.999873,
    #                first divergence not a tie).
    #   "exact_hi" - fp32-grade scores (HIGHEST = 3 bf16 MXU passes)
    #                inside the merged top-1 scan kernel + exact fp32
    #                re-score.  The round-2 parity baseline and the
    #                sharded path's scan; A/B seam for exact_hi2.
    #   "auto"     - per level: exact_hi2_2p when the DB row count reaches
    #                the measured crossover (backends/tpu.py
    #                _PACKED_CROSSOVER_ROWS — the ONE definition),
    #                exact_hi below it.
    # Gated behind IA_EXPERIMENTAL=1 (non-parity A/B probes — see
    # EXPERIMENTAL_MATCH_MODES above): "scan_rescue" (bf16 per-tile
    # champion scan + top-8 fp32 rescue), "two_pass" (bf16 global top-2 +
    # fp32 re-score), and their single-scan-pass "_1p" variants.
    match_mode: str = "auto"

    # Use the cKDTree index for the CPU approximate match (the reference's ANN
    # toggle); False = brute force (native C++ matcher if built, else NumPy).
    use_ann: bool = True

    # Parallelism (SURVEY.md §5.7-5.8): shard the A/A' patch DB over `db_shards`
    # mesh devices; video mode shards frames over `data_shards` devices of the
    # (data, db) mesh (BASELINE.json:12) — `models/video.py` dispatches the
    # two_phase scheme through `parallel/step.py` when data_shards > 1.
    db_shards: int = 1
    data_shards: int = 1

    # Video mode: weight of the temporal-coherence feature term (previous
    # frame's B' window appended to the feature vector, BASELINE.json:12).
    temporal_weight: float = 0.0

    # Aux subsystems (SURVEY.md §5)
    # §5.3 failure recovery: retry a level this many times on transient
    # device/runtime faults (level granularity — combine with checkpoint_dir
    # so a process restart after exhausted retries loses at most one level).
    level_retries: int = 0
    # Watchdog around each level's device dispatch: > 0 runs the dispatch
    # on a helper thread and raises a TRANSIENT WatchdogTimeout when it
    # exceeds this many seconds (a wedged op becomes a retry, not a hung
    # process).  0 (default) dispatches inline — no thread, no overhead.
    # Pair with level_retries so the timeout actually recovers.
    dispatch_timeout_s: float = 0.0
    # §5.5 observability vs pipelining: with True (default) the driver
    # synchronizes after each level so per-level `ms` / `pixels_per_s`
    # stats measure real device time.  False lets all levels' device work
    # ENQUEUE back-to-back (one sync before the final fetch) — on a
    # high-latency dispatch link this pipelines host prep under device
    # compute and removes per-level round-trips; per-level stats then
    # report `enqueue_ms` instead of `ms` (they no longer measure
    # compute).  bench.py uses False: the north-star metric is synthesis
    # wall-clock, not per-level telemetry.  level_retries > 0 forces the
    # sync regardless (faults must surface inside the retry wrapper).
    level_sync: bool = True
    checkpoint_dir: Optional[str] = None  # per-level checkpoints if set
    resume_from_level: Optional[int] = None  # level index (finest=0) to resume at
    profile_dir: Optional[str] = None  # jax.profiler trace dir if set
    log_path: Optional[str] = None  # JSONL structured per-level records
    # Run-scoped observability (obs/): True installs a per-run metrics
    # registry + span tracing (run_id-stamped JSONL records, run manifest,
    # run_end counter snapshot — analyzed by `ia report`).  Off by default
    # and near-zero-cost when off: the instrumentation sites reduce to one
    # module-bool check, so bench numbers don't move.  Setting log_path
    # alone also activates the run scope (a log implies observability);
    # this flag additionally enables it without a log file (counters land
    # in AnalogyResult-adjacent logging only).
    metrics: bool = False
    # Write each level's synthesized B' plane as level_XX.png into this dir
    # (the reference family's de-facto debug behavior): visual debugging of
    # coarse-to-fine progress without touching checkpoints.
    save_levels_dir: Optional[str] = None

    # tune/ subsystem (perf PR 3).
    # Shape bucketing: round each level's padded DB row count up to a
    # small bucket set so differently-sized A exemplars share jit program
    # signatures (tune/buckets.py; the A dims ride along as a traced
    # leaf).  Off by default — identical programs to the pre-bucketing
    # engine; env IA_SHAPE_BUCKETS overrides either way.
    shape_buckets: bool = False
    # JAX persistent compilation cache directory (env IA_COMPILE_CACHE_DIR
    # overrides).  Pairs with `ia warmup`: pre-compile once, reuse across
    # process restarts.
    compile_cache_dir: Optional[str] = None
    # Device-upload cache byte budget (utils/devcache.py); None keeps the
    # 1 GiB default, env IA_DEVCACHE_BYTES overrides.
    devcache_max_bytes: Optional[int] = None

    # catalog/ subsystem (ROADMAP item 4): content-addressed exemplar
    # catalog root.  When set (or env IA_CATALOG_DIR), the driver
    # resolves each level's A-side features tier-by-tier (resident →
    # host RAM → sealed disk artifact → cold build) instead of always
    # building in the request path; every tier serves bit-identical
    # bytes to a cold build.  None disables catalog consultation.
    catalog_dir: Optional[str] = None
    # Host-RAM tier byte budget; None keeps the 256 MiB default, env
    # IA_CATALOG_HOST_BYTES overrides.
    catalog_host_bytes: Optional[int] = None

    # Async pipelined engine (perf PR 8).
    # Host/device overlap: while level d's program is in flight, a helper
    # thread warms level d-1's host-side inputs (devcache uploads, the
    # anti-diagonal schedule, gather maps) so the next dispatch finds hot
    # caches instead of doing that work on the critical path.  Prefetch
    # only WARMS content/shape-keyed caches — the dispatch path consults
    # the same caches and recomputes on a miss, so results are
    # bit-identical to the sequential driver by construction.  None
    # (default) = auto: on when level_sync=False and level_retries == 0
    # (the bench configuration); True forces it (including on CPU, for
    # the bit-identity tests); False disables.  level_retries > 0 always
    # disables it: a prefetch fault would surface OUTSIDE the §5.3 retry
    # wrapper, so lock-step mode stays strictly sequential.
    pipeline: Optional[bool] = None
    # Buffer donation: the per-level runners and the chained coarser-B'
    # plane run through donate_argnums twins so XLA reuses the level's
    # input buffers for its outputs instead of allocating fresh HBM.
    # None (default) = auto: donate when running on a real TPU backend
    # and nothing else can read the donated buffers (level_retries == 0,
    # no keep_levels/checkpoint/save_levels consumers); True forces the
    # donating code path even on CPU, where jax ignores donation with a
    # warning — semantics identical, which is what the bit-identity test
    # pins; False disables.  level_retries > 0 always disables donation:
    # retries rebuild from host copies and must be able to re-read every
    # input (§5.3 fault model).
    donate_buffers: Optional[bool] = None
    # Opt-in bf16 candidate scoring for the wavefront anchor scan: score
    # the candidate sweep in bf16 (half the HBM traffic), then re-score
    # the top-k survivors in exact f32 with the engine's lowest-index
    # tie-break.  OFF by default and gated behind the oracle-parity
    # audit: first use on a device runs a small probe twice (exact vs
    # bf16) and audits the source maps (utils/parity.py); any mismatch
    # not explained as an exact/fp tie auto-disables the flag for the
    # process (counter bf16.disabled_unexplained, event bf16_gate).
    # Unlike IA_EXPERIMENTAL match modes, this is a supported production
    # flag BECAUSE of that gate — it refuses to run non-parity.
    bf16_scoring: bool = False
    # Opt-in two-stage ANN matcher (ROADMAP item 3): a cheap prefilter
    # over a PCA-projected copy of the A/A' DB selects a top-m candidate
    # slab per query, then the existing exact-f32 scorer re-scores only
    # that slab — per-pixel cost goes from O(|A|) toward O(m + proj).
    # OFF by default and gated exactly like bf16_scoring: first use on a
    # device class runs a small probe twice (exact vs two-stage) and
    # audits the source maps (utils/parity.py); any mismatch not
    # explained as a tie auto-disables the flag for the process (counter
    # ann.disabled_unexplained, event ann_gate) and synthesis silently
    # stays exact.  Slab size / projection rank resolve through tune/
    # (ann_top_m / ann_proj_dims; env IA_ANN_TOP_M / IA_ANN_PROJ_DIMS).
    # Projection matrices are sha256-sealed catalog artifacts when a
    # catalog root is configured (built at `ia catalog build`), else
    # computed on the fly from the level's DB.
    ann_prefilter: bool = False

    def __post_init__(self):
        if self.levels < 1:
            raise ValueError(f"levels must be >= 1, got {self.levels}")
        for name in ("patch_size", "coarse_patch_size"):
            v = getattr(self, name)
            if v < 1 or v % 2 == 0:
                raise ValueError(f"{name} must be odd and >= 1, got {v}")
        if self.kappa < 0:
            raise ValueError(f"kappa must be >= 0, got {self.kappa}")
        if self.color_mode not in ("yiq_transfer", "source_rgb"):
            raise ValueError(f"unknown color_mode {self.color_mode!r}")
        if self.backend not in ("cpu", "tpu"):
            raise ValueError(f"unknown backend {self.backend!r}")
        if self.strategy not in ("exact", "rowwise", "batched", "wavefront",
                                 "auto"):
            raise ValueError(f"unknown strategy {self.strategy!r}")
        if self.match_mode not in PARITY_MATCH_MODES:
            if self.match_mode in EXPERIMENTAL_MATCH_MODES:
                if not experimental_enabled():
                    raise ValueError(
                        f"match_mode {self.match_mode!r} is a non-parity "
                        "experimental A/B probe (its bf16-resolution scan "
                        "drifts from the oracle — see "
                        "experiments/rescue_probe.py); set IA_EXPERIMENTAL=1 "
                        "to enable it, or use one of "
                        f"{PARITY_MATCH_MODES}")
            else:
                raise ValueError(f"unknown match_mode {self.match_mode!r}")
        if self.level_retries < 0:
            raise ValueError(
                f"level_retries must be >= 0, got {self.level_retries}")
        if self.refine_passes < 0:
            raise ValueError(
                f"refine_passes must be >= 0, got {self.refine_passes}")
        if self.db_shards < 1:
            raise ValueError(f"db_shards must be >= 1, got {self.db_shards}")
        if self.data_shards < 1:
            raise ValueError(
                f"data_shards must be >= 1, got {self.data_shards}")
        if self.devcache_max_bytes is not None and self.devcache_max_bytes < 1:
            raise ValueError(
                "devcache_max_bytes must be positive when set, got "
                f"{self.devcache_max_bytes}")
        if (self.catalog_host_bytes is not None
                and self.catalog_host_bytes < 1):
            raise ValueError(
                "catalog_host_bytes must be positive when set, got "
                f"{self.catalog_host_bytes}")
        if self.bf16_scoring and self.backend != "tpu":
            raise ValueError(
                "bf16_scoring applies to the TPU wavefront scan; "
                f"backend {self.backend!r} has no bf16 candidate path")
        if self.bf16_scoring and self.strategy not in ("wavefront", "auto"):
            raise ValueError(
                "bf16_scoring requires strategy 'wavefront' or 'auto', "
                f"got {self.strategy!r}")
        if self.ann_prefilter and self.backend != "tpu":
            raise ValueError(
                "ann_prefilter is the TPU engine's two-stage matcher; "
                f"backend {self.backend!r} has its own ANN toggle "
                "(use_ann)")
        if self.ann_prefilter and self.strategy not in ("wavefront",
                                                        "batched", "auto"):
            raise ValueError(
                "ann_prefilter requires strategy 'wavefront', 'batched' "
                f"or 'auto', got {self.strategy!r}")

    def pipeline_active(self) -> bool:
        """Resolved pipeline flag: explicit setting wins, auto enables the
        prefetch thread only in the async-dispatch configuration; retries
        always force lock-step (see the `pipeline` field comment)."""
        if self.level_retries > 0:
            return False
        if self.pipeline is not None:
            return self.pipeline
        return not self.level_sync

    def replace(self, **kw) -> "AnalogyParams":
        return dataclasses.replace(self, **kw)

    @property
    def fine_radius(self) -> int:
        return self.patch_size // 2

    @property
    def coarse_radius(self) -> int:
        return self.coarse_patch_size // 2

    def kappa_factor(self, level: int) -> float:
        """Coherence threshold multiplier at `level` (0 = finest).

        Hertzmann §3.2: 1 + 2^(l-L) * kappa with l counted coarsest->finest;
        with our finest-first numbering that is 1 + 2^(-level) * kappa.
        Squared by callers because we compare squared distances.
        """
        return 1.0 + (2.0 ** (-level)) * self.kappa


# Preset configs matching the five required eval configs (BASELINE.json:7-12).
PRESETS = {
    "texture_by_numbers": AnalogyParams(
        levels=1, patch_size=5, kappa=1.0, remap_luminance=False,
        color_mode="source_rgb",
    ),
    "oil_filter": AnalogyParams(levels=3, patch_size=5, kappa=5.0),
    "super_resolution": AnalogyParams(levels=2, patch_size=7, kappa=0.5),
    "npr_1024": AnalogyParams(levels=5, patch_size=5, kappa=5.0),
    "texture_synthesis": AnalogyParams(
        levels=3, patch_size=5, kappa=2.0, remap_luminance=False,
        src_weight=0.0, color_mode="source_rgb",
    ),
    # video is the multi-chip flagship (frames shard over the mesh):
    # backend defaults to tpu so --data-shards works without extra flags
    "video": AnalogyParams(levels=3, patch_size=5, kappa=5.0,
                           temporal_weight=1.0, backend="tpu"),
}
