// Native brute-force L2 argmin matcher for the CPU backend.
//
// The reference's hot path lives in SciPy's C/Cython cKDTree (SURVEY.md §2.2
// N1); this is the framework's native equivalent for the ANN-off path:
// exact nearest rows of a (n x f) float32 database for a batch of queries,
// OpenMP-parallel over queries, blocked over DB rows for cache locality,
// ties resolved to the lowest index (matching the Pallas kernel and the
// NumPy fallback in backends/native_match.py).
//
// Build: make -C native        (produces libia_match.so, loaded via ctypes)

#include <cfloat>
#include <cstdint>

extern "C" {

void ia_brute_argmin(const float *db, int64_t n, int64_t f,
                     const float *queries, int64_t m,
                     int64_t *out_idx, float *out_dist) {
#pragma omp parallel for schedule(static)
  for (int64_t q = 0; q < m; ++q) {
    const float *qv = queries + q * f;
    float best = FLT_MAX;
    int64_t best_i = 0;
    for (int64_t i = 0; i < n; ++i) {
      const float *row = db + i * f;
      float acc = 0.0f;
      for (int64_t k = 0; k < f; ++k) {
        const float d = row[k] - qv[k];
        acc += d * d;
      }
      if (acc < best) {  // strict: first minimum wins -> lowest index
        best = acc;
        best_i = i;
      }
    }
    out_idx[q] = best_i;
    out_dist[q] = best;
  }
}

}  // extern "C"
